"""Advisor algorithms: protocol mechanics + convergence sanity."""

import math

import pytest

from rafiki_tpu.advisor import (ADVISOR_REGISTRY, Proposal, TrialResult,
                                make_advisor)
from rafiki_tpu.model.knob import (CategoricalKnob, FixedKnob, FloatKnob,
                                   IntegerKnob, PolicyKnob)


def quadratic_score(knobs):
    """Smooth objective with max 1.0 at lr=1e-2, width=128."""
    lr_term = -((math.log10(knobs["lr"]) + 2.0) ** 2) / 4.0
    w_term = -((math.log2(knobs["width"]) - 7.0) ** 2) / 16.0
    return 1.0 + lr_term + w_term


def search_config():
    return {
        "lr": FloatKnob(1e-5, 1e-1, is_exp=True),
        "width": IntegerKnob(16, 512, is_exp=True),
        "const": FixedKnob("adam"),
    }


def run_search(advisor, objective, budget_scale_aware=False):
    trial_id = 0
    while True:
        p = advisor.propose()
        if not p.is_valid:
            break
        score = objective(p.knobs)
        if budget_scale_aware:
            # low-budget trials see a noisier/worse version of the truth
            score = score * (0.5 + 0.5 * p.budget_scale)
        advisor.feedback(TrialResult(
            trial_no=p.trial_no, knobs=p.knobs, score=score,
            trial_id=f"t{trial_id}", budget_scale=p.budget_scale,
            meta=p.meta))
        trial_id += 1
    return advisor


def test_registry_has_all_algorithms():
    assert {"random", "bayes_gp", "bohb"} <= set(ADVISOR_REGISTRY)


def test_random_respects_trial_budget():
    adv = make_advisor(search_config(), "random", total_trials=7)
    run_search(adv, quadratic_score)
    assert len(adv.results) == 7
    assert adv.finished
    assert not adv.propose().is_valid


def test_bayes_gp_beats_random():
    n = 30
    rnd = run_search(make_advisor(search_config(), "random",
                                  total_trials=n, seed=0), quadratic_score)
    gp = run_search(make_advisor(search_config(), "bayes_gp",
                                 total_trials=n, seed=0), quadratic_score)
    assert gp.best is not None and rnd.best is not None
    # GP should find a near-optimal point; random merely a decent one
    assert gp.best.score >= rnd.best.score - 0.05
    assert gp.best.score > 0.9


def test_bayes_gp_constant_liar_outstanding():
    adv = make_advisor(search_config(), "bayes_gp", total_trials=20, seed=1)
    # take several proposals before any feedback (concurrent workers)
    props = [adv.propose() for _ in range(5)]
    assert all(p.is_valid for p in props)
    for p in props:
        adv.feedback(TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                                 score=quadratic_score(p.knobs)))
    run_search(adv, quadratic_score)
    assert len(adv.results) == 20


def bohb_config():
    cfg = search_config()
    cfg["quick"] = PolicyKnob("QUICK_TRAIN")
    cfg["share"] = PolicyKnob("SHARE_PARAMS")
    return cfg


def test_bohb_rungs_and_promotion():
    adv = make_advisor(bohb_config(), "bohb", total_trials=30, seed=0)
    assert adv.name == "bohb"
    run_search(adv, quadratic_score, budget_scale_aware=True)
    scales = [r.budget_scale for r in adv.results]
    # some trials ran at reduced budget, some at full
    assert any(s < 1.0 for s in scales)
    assert any(s >= 1.0 for s in scales)
    # promotions warm-start from their parent's checkpoint
    promoted = [r for r in adv.results if r.meta.get("rung", 0) > 0]
    assert promoted, "no trial was ever promoted"
    assert adv.best is not None and adv.best.budget_scale >= 1.0


def test_bohb_promotion_chain_reaches_full_budget():
    adv = make_advisor(bohb_config(), "bohb", total_trials=60, seed=2)
    run_search(adv, quadratic_score, budget_scale_aware=True)
    top_rung = max(r.meta.get("rung", 0) for r in adv.results)
    assert adv.budgets[top_rung] == 1.0


def test_bohb_small_budget_still_yields_full_budget_best():
    """With a tiny trial budget the rungs can't promote organically; the
    final-trial reservation must still produce a full-budget best."""
    for n in (1, 2, 4):
        adv = make_advisor(bohb_config(), "bohb", total_trials=n, seed=0)
        run_search(adv, quadratic_score, budget_scale_aware=True)
        assert len(adv.results) == n
        assert adv.best is not None and adv.best.budget_scale >= 1.0
        assert adv.best_effort is adv.best


def test_best_effort_falls_back_to_highest_budget():
    adv = make_advisor(search_config(), "random", total_trials=3, seed=0)
    # feed only low-budget results (as if the job was stopped mid-bracket)
    for i in range(3):
        p = adv.propose()
        adv.feedback(TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                                 score=float(i), trial_id=f"t{i}",
                                 budget_scale=1.0 / 3.0))
    assert adv.best is None
    be = adv.best_effort
    assert be is not None and be.score == 2.0


def test_bohb_errored_trials_dont_block():
    adv = make_advisor(bohb_config(), "bohb", total_trials=12, seed=3)
    ok = 0
    while True:
        p = adv.propose()
        if not p.is_valid:
            break
        if p.trial_no % 3 == 0:
            adv.trial_errored(p.trial_no)
            continue
        adv.feedback(TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                                 score=quadratic_score(p.knobs),
                                 budget_scale=p.budget_scale, meta=p.meta))
        ok += 1
    assert ok > 0
    assert adv.finished


def test_auto_selection():
    assert make_advisor(bohb_config(), "auto").name == "bohb"
    assert make_advisor(search_config(), "auto").name == "bayes_gp"
    assert make_advisor({"c": FixedKnob(1)}, "auto").name == "random"


def test_advisor_service_round_trip():
    from rafiki_tpu.advisor.service import AdvisorClient, AdvisorService

    adv = make_advisor(search_config(), "random", total_trials=4, seed=0)
    svc = AdvisorService(adv)
    host, port = svc.start()
    try:
        client = AdvisorClient(f"http://{host}:{port}")
        n = 0
        while True:
            p = client.propose()
            if not p.is_valid:
                break
            client.feedback(TrialResult(
                trial_no=p.trial_no, knobs=p.knobs,
                score=quadratic_score(p.knobs), trial_id=f"t{n}"))
            n += 1
        assert n == 4
        status = client.status()
        assert status["finished"] is True
        assert status["n_results"] == 4
        assert status["best"]["score"] > 0
    finally:
        svc.stop()


def test_bohb_quick_train_only_on_subfull_rungs():
    # a full-budget (scale 1.0) proposal must NOT carry QUICK_TRAIN: models
    # cap epochs under it, which would make rung budgets indistinguishable
    adv = make_advisor(bohb_config(), "bohb", total_trials=40, seed=3)
    run_search(adv, quadratic_score, budget_scale_aware=True)
    full = [r for r in adv.results if r.budget_scale >= 1.0]
    sub = [r for r in adv.results if r.budget_scale < 1.0]
    assert full and sub
    assert all(r.knobs["quick"] is False for r in full)
    assert all(r.knobs["quick"] is True for r in sub)


def test_bohb_concurrent_workers_race_final_trial():
    """VERDICT r2 weak #8: N threads hammer propose/feedback concurrently.
    Invariants under the race: trial_nos are unique, the budget is never
    exceeded, at least one full-budget (budget_scale>=1.0) trial runs,
    and best_effort lands on a real result."""
    import threading

    from rafiki_tpu.advisor import TrialResult, make_advisor
    from rafiki_tpu.model import FloatKnob, IntegerKnob

    knob_config = {"lr": FloatKnob(1e-4, 1e-1, is_exp=True),
                   "width": IntegerKnob(8, 64)}
    total = 12
    adv = make_advisor(knob_config, "bohb", total_trials=total, seed=0)

    seen_nos = []
    seen_lock = threading.Lock()

    def worker(tid: int) -> None:
        while True:
            p = adv.propose()
            if not p.is_valid:
                return
            with seen_lock:
                seen_nos.append(p.trial_no)
            # score correlates with lr so promotions actually happen
            score = 1.0 - abs(float(p.knobs["lr"]) - 1e-2)
            adv.feedback(TrialResult(
                trial_no=p.trial_no, knobs=p.knobs, score=score,
                budget_scale=p.budget_scale, trial_id=f"t{p.trial_no}"))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(seen_nos) == total
    assert sorted(set(seen_nos)) == sorted(seen_nos), "duplicate trial_no"
    assert adv.finished
    full = [r for r in adv.results if r.budget_scale >= 1.0]
    assert full, "final-trial reservation must guarantee a full-budget run"
    assert adv.best_effort is not None
    assert adv.best_effort.budget_scale >= 1.0


def test_bohb_tpe_survives_high_dim_small_sample():
    """Regression: with more search dimensions than top-quantile points
    (any 4-knob template after ~8 completions) the TPE KDE covariance
    is singular and scipy raises ValueError — the sampler must fall
    back to random exploration, not crash the advisor."""
    cfg = {f"k{i}": FloatKnob(0.0, 1.0) for i in range(4)}
    adv = make_advisor(cfg, "bohb", total_trials=24, seed=0)
    run_search(adv, lambda knobs: sum(knobs[f"k{i}"] for i in range(4)),
               budget_scale_aware=True)
    assert len(adv.results) == 24
    assert adv.best_effort is not None


@pytest.mark.parametrize("advisor_type", ["random", "bohb"])
def test_propose_batch_equals_sequential_proposes(advisor_type):
    """Batched-advisor determinism: propose_batch(k) must hand out the
    exact knob sets k sequential propose() calls would (same seed →
    same proposals, regardless of lane count), and stay deterministic
    across identically-fed advisors."""
    cfg = bohb_config() if advisor_type == "bohb" else search_config()
    a = make_advisor(cfg, advisor_type, total_trials=24, seed=11)
    b = make_advisor(cfg, advisor_type, total_trials=24, seed=11)
    batch = a.propose_batch(6)
    seq = [b.propose() for _ in range(6)]
    assert [p.knobs for p in batch] == [p.knobs for p in seq]
    assert [p.budget_scale for p in batch] == [p.budget_scale for p in seq]
    # identical feedback → identical NEXT batches (rung/posterior state
    # advances the same way through the batched verbs)
    results = [TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                           score=quadratic_score(p.knobs),
                           trial_id=f"t{p.trial_no}",
                           budget_scale=p.budget_scale, meta=p.meta)
               for p in batch]
    a.feedback_batch(results)
    for r in results:
        b.feedback(r)
    batch2 = a.propose_batch(4)
    seq2 = [b.propose() for _ in range(4)]
    assert [p.knobs for p in batch2] == [p.knobs for p in seq2]
    assert [p.warm_start_trial_id for p in batch2] == \
        [p.warm_start_trial_id for p in seq2]


def test_propose_batch_respects_budget_and_lane_count():
    adv = make_advisor(search_config(), "random", total_trials=5, seed=0)
    batch = adv.propose_batch(8)  # more lanes than budget
    assert len(batch) == 5
    assert [p.trial_no for p in batch] == [0, 1, 2, 3, 4]
    assert adv.propose_batch(3) == []
    # lane count does not change the knob stream: a same-seed advisor
    # pulled in different batch sizes sees the same sequence
    a = make_advisor(search_config(), "random", total_trials=6, seed=3)
    b = make_advisor(search_config(), "random", total_trials=6, seed=3)
    knobs_a = [p.knobs for p in a.propose_batch(2)] + \
        [p.knobs for p in a.propose_batch(4)]
    knobs_b = [p.knobs for p in b.propose_batch(6)]
    assert knobs_a == knobs_b


def test_advisor_service_batch_verbs():
    from rafiki_tpu.advisor.service import AdvisorClient, AdvisorService

    adv = make_advisor(bohb_config(), "bohb", total_trials=6, seed=4)
    ref = make_advisor(bohb_config(), "bohb", total_trials=6, seed=4)
    svc = AdvisorService(adv)
    host, port = svc.start()
    try:
        client = AdvisorClient(f"http://{host}:{port}")
        batch = client.propose_batch(6)
        assert [p.knobs for p in batch] == \
            [p.knobs for p in ref.propose_batch(6)]
        client.feedback_batch([
            TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                        score=quadratic_score(p.knobs),
                        trial_id=f"t{p.trial_no}",
                        budget_scale=p.budget_scale, meta=p.meta)
            for p in batch])
        assert client.status()["n_results"] == 6
    finally:
        svc.stop()


def test_arch_evolution_advisor():
    """ENAS-lite: seeds a random population, then mutates tournament
    winners; a non-shape mutation inherits the parent's params
    (warm_start), a shape mutation does not."""
    from rafiki_tpu.advisor import TrialResult, make_advisor
    from rafiki_tpu.model import (CategoricalKnob, FloatKnob, IntegerKnob,
                                  PolicyKnob)

    knob_config = {
        "width": CategoricalKnob([32, 64, 128], shape_relevant=True),
        "depth": IntegerKnob(2, 6, shape_relevant=True),
        "lr": FloatKnob(1e-4, 1e-1, is_exp=True),
        "share": PolicyKnob("SHARE_PARAMS"),
    }
    total = 24
    adv = make_advisor(knob_config, "arch_evo", total_trials=total,
                       seed=3, population=4, sample_size=2)
    warm_starts = 0
    shape_mutations = 0
    for _ in range(total):
        p = adv.propose()
        assert p.is_valid
        assert p.knobs["share"] is True
        if p.warm_start_trial_id:
            warm_starts += 1
            # inherited weights require identical shapes
            parent = next(r for r in adv.results
                          if r.trial_no == p.meta["parent_trial_no"])
            from rafiki_tpu.model.knob import shape_signature
            assert shape_signature(knob_config, parent.knobs) == \
                shape_signature(knob_config, p.knobs)
        if p.meta.get("mutated") in ("width", "depth"):
            shape_mutations += 1
        # score favors wide+deep so evolution has a gradient to climb
        score = (0.3 * (p.knobs["width"] / 128)
                 + 0.3 * (p.knobs["depth"] / 6)
                 + 0.1 * adv._rng.random())
        adv.feedback(TrialResult(trial_no=p.trial_no, knobs=p.knobs,
                                 score=score, trial_id=f"t{p.trial_no}"))
    assert not adv.propose().is_valid  # budget exhausted
    assert warm_starts > 0, "lr-only mutations should inherit params"
    assert shape_mutations > 0, "architecture dims should be explored"
    # evolution should concentrate on better architectures over time
    late = sum(r.score for r in adv.results[-8:]) / 8
    early = sum(r.score for r in adv.results[:8]) / 8
    assert late >= early - 0.05
