"""Preemption-safe trials (SURVEY.md §5.3): mid-train checkpoints +
atomic claim + warm resume of trials a dead worker left behind."""

from typing import Any, Optional

import numpy as np
import pytest

from rafiki_tpu.advisor.base import make_advisor
from rafiki_tpu.model.base import BaseModel, TrainContext
from rafiki_tpu.model.knob import FixedKnob, PolicyKnob
from rafiki_tpu.store.meta_store import MetaStore
from rafiki_tpu.store.param_store import ParamStore
from rafiki_tpu.worker.train import TrainWorker


class ToyModel(BaseModel):
    """5-"epoch" counter model: w += 1 per epoch, checkpointing each one.
    Evaluate returns w, so a warm resume is visible as w > fresh-train w."""

    TASKS = ("IMAGE_CLASSIFICATION",)
    FAIL_AT: Optional[int] = None  # raise after this epoch's checkpoint

    @staticmethod
    def get_knob_config():
        return {"max_epochs": FixedKnob(5),
                "share_params": PolicyKnob("SHARE_PARAMS")}

    def __init__(self, **knobs: Any) -> None:
        super().__init__(**knobs)
        self._w = None

    def train(self, dataset_path: str,
              ctx: Optional[TrainContext] = None) -> None:
        ctx = ctx or TrainContext()
        self._w = np.zeros(())
        if ctx.shared_params is not None and self.knobs.get("share_params"):
            self._w = np.asarray(ctx.shared_params["w"])
        epochs = max(1, round(5 * float(ctx.budget_scale)))
        for epoch in range(epochs):
            self._w = self._w + 1.0
            if ctx.checkpoint is not None:
                # like the real templates: fraction of the ASSIGNED
                # budget (the worker maps it to global progress)
                ctx.checkpoint(self.dump_parameters,
                               frac_done=(epoch + 1) / epochs)
            if self.FAIL_AT is not None and epoch >= self.FAIL_AT:
                raise RuntimeError("simulated preemption")

    def evaluate(self, dataset_path: str) -> float:
        return float(self._w)

    def predict(self, queries):
        return [0 for _ in queries]

    def dump_parameters(self):
        return {"w": np.asarray(self._w)}

    def load_parameters(self, params):
        self._w = np.asarray(params["w"])


class FlakyToyModel(ToyModel):
    FAIL_AT = 2  # dies with w == 3 checkpointed


def _worker(model_class, meta, store, sub_id, wid, trials):
    return TrainWorker(
        model_class=model_class,
        advisor=make_advisor(model_class.get_knob_config(), "random",
                             total_trials=trials),
        train_dataset_path="unused", val_dataset_path="unused",
        param_store=store, meta_store=meta, sub_train_job_id=sub_id,
        model_id="m0", worker_id=wid,
        checkpoint_interval_s=1e-9)  # checkpoint every epoch


@pytest.fixture()
def stores(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("u@x", "pw", "ADMIN")
    model = meta.create_model(user["id"], "toy", "IMAGE_CLASSIFICATION",
                              "ToyModel", b"")
    job = meta.create_train_job(user["id"], "app", 1,
                                "IMAGE_CLASSIFICATION", {"TRIAL_COUNT": 1},
                                "tr", "va")
    sub = meta.create_sub_train_job(job["id"], model["id"])
    return meta, ParamStore.from_uri("mem://"), sub["id"]


def test_preempted_trial_leaves_checkpoint(stores):
    meta, store, sub_id = stores
    w = _worker(FlakyToyModel, meta, store, sub_id, "w0", trials=1)
    w.run(max_trials=1)
    trials = meta.get_trials_of_sub_train_job(sub_id)
    assert len(trials) == 1 and trials[0]["status"] == "ERRORED"
    ckpt = store.load(f"ckpt-{trials[0]['id']}")
    assert ckpt is not None and float(np.asarray(ckpt["w"])) == 3.0


def test_resume_finishes_orphan_warm(stores):
    meta, store, sub_id = stores
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    old = meta.get_trials_of_sub_train_job(sub_id)[0]

    # a replacement worker picks the orphan up before asking the advisor
    w2 = _worker(ToyModel, meta, store, sub_id, "w1", trials=0)
    assert w2.resume_orphaned_trials() == 1

    trials = meta.get_trials_of_sub_train_job(sub_id)
    by_status = {t["status"]: t for t in trials}
    assert by_status["TERMINATED"]["id"] == old["id"]
    assert "resumed by w1" in by_status["TERMINATED"]["error"]
    done = by_status["COMPLETED"]
    assert done["trial_no"] == old["trial_no"]
    # warm start + remaining-budget scaling: resumed from w=3 with
    # frac_done=3/5, so it trains round(5*0.4)=2 more epochs → 5, the
    # SAME total budget an un-preempted trial gets (scores comparable)
    assert done["score"] == 5.0
    # the orphan's checkpoint is consumed; the resumed trial's own
    # checkpoint is superseded by its final params
    assert store.load(f"ckpt-{old['id']}") is None
    assert store.load(f"ckpt-{done['id']}") is None
    assert store.load(done["id"]) is not None  # final params saved


def test_claim_is_exclusive(stores):
    meta, store, sub_id = stores
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    tid = meta.get_trials_of_sub_train_job(sub_id)[0]["id"]
    assert meta.claim_trial_for_resume(tid, "w1") is True
    assert meta.claim_trial_for_resume(tid, "w2") is False


def test_completed_trials_never_resumed(stores):
    meta, store, sub_id = stores
    _worker(ToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    w2 = _worker(ToyModel, meta, store, sub_id, "w1", trials=0)
    assert w2.resume_orphaned_trials() == 0


def test_deterministic_failure_never_resumed(stores):
    """ADVICE r3 (medium): a code/knob crash recorded by a live worker is
    NOT an orphan — peers re-running it would reproduce the crash (and
    double-feed the advisor when a resume completes)."""
    meta, store, sub_id = stores

    class BuggyModel(ToyModel):
        def train(self, dataset_path, ctx=None):
            raise ValueError("bad knob combination")  # deterministic

    _worker(BuggyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    t = meta.get_trials_of_sub_train_job(sub_id)[0]
    assert t["status"] == "ERRORED"
    assert t["error_class"] == "deterministic"

    w2 = _worker(ToyModel, meta, store, sub_id, "w1", trials=0)
    assert w2.resume_orphaned_trials() == 0
    # even a direct claim refuses a deterministic ERRORED row
    assert meta.claim_trial_for_resume(t["id"], "w1") is False
    assert meta.get_trial(t["id"])["status"] == "ERRORED"


def test_error_classification():
    from rafiki_tpu.worker.train import classify_trial_error

    # infra-class: resumable elsewhere
    assert classify_trial_error(OSError("connection reset")) == "preemption"
    assert classify_trial_error(MemoryError()) == "preemption"
    assert classify_trial_error(
        RuntimeError("UNAVAILABLE: TPU device lost")) == "preemption"
    assert classify_trial_error(
        RuntimeError("worker preempted by scheduler")) == "preemption"
    # code bugs: deterministic, never resumed
    assert classify_trial_error(ValueError("bad knob")) == "deterministic"
    assert classify_trial_error(KeyError("params")) == "deterministic"
    assert classify_trial_error(
        ZeroDivisionError()) == "deterministic"


def test_preemption_class_errored_is_resumed(stores):
    """FlakyToyModel's 'simulated preemption' classifies as infra-class,
    so the recorded ERRORED row stays claimable (the round-3 behavior,
    now opt-in via error_class)."""
    meta, store, sub_id = stores
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    t = meta.get_trials_of_sub_train_job(sub_id)[0]
    assert t["status"] == "ERRORED" and t["error_class"] == "preemption"
    # and it IS claimable/resumable by a peer — guards the claim SQL's
    # error_class gate, not just the recorded label
    w2 = _worker(ToyModel, meta, store, sub_id, "w1", trials=0)
    assert w2.resume_orphaned_trials() == 1
    done = [x for x in meta.get_trials_of_sub_train_job(sub_id)
            if x["status"] == "COMPLETED"]
    assert len(done) == 1 and done[0]["score"] == 5.0


def test_worker_never_resumes_own_failure(stores):
    meta, store, sub_id = stores
    w = _worker(FlakyToyModel, meta, store, sub_id, "w0", trials=2)
    w.run(max_trials=2)  # in-loop orphan scan must skip its own wrecks
    trials = meta.get_trials_of_sub_train_job(sub_id)
    assert all(t["status"] == "ERRORED" for t in trials), trials
    assert len(trials) == 2  # two advisor proposals, zero self-resumes


def test_resume_cap_bounds_pingpong(stores):
    meta, store, sub_id = stores
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    w2 = _worker(FlakyToyModel, meta, store, sub_id, "w1", trials=0)
    w2.max_resumes = 1
    # resumed trial ALSO crashes (leaves its own orphan under w1) but the
    # cap stops w1 from chasing anything further
    assert w2.resume_orphaned_trials() == 1
    assert w2.resume_orphaned_trials() == 0


def test_live_peer_trial_is_not_hijacked(stores):
    meta, store, sub_id = stores
    # simulate worker A 40s into a trial, heartbeating normally
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.heartbeat_trial(t["id"])
    store.save(f"ckpt-{t['id']}", {"w": np.asarray(2.0)})

    w2 = _worker(ToyModel, meta, store, sub_id, "wB", trials=0)
    assert w2.resume_orphaned_trials() == 0  # fresh heartbeat → hands off
    assert meta.get_trial(t["id"])["status"] == "RUNNING"
    # claim with an artificially generous staleness still refuses
    assert meta.claim_trial_for_resume(t["id"], "wB",
                                       stale_after_s=60.0) is False


def test_stale_running_trial_is_resumed(stores):
    meta, store, sub_id = stores
    # dead worker: RUNNING trial, heartbeat long gone, ckpt present
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.update_trial(t["id"], heartbeat_at=0.0)  # epoch 1970
    store.save(f"ckpt-{t['id']}", {"w": np.asarray(3.0)})
    store.save(f"ckpt-{t['id']}-meta", {"frac_done": 3 / 5})

    w2 = _worker(ToyModel, meta, store, sub_id, "wB", trials=0)
    assert w2.resume_orphaned_trials() == 1
    done = [x for x in meta.get_trials_of_sub_train_job(sub_id)
            if x["status"] == "COMPLETED"]
    assert len(done) == 1 and done[0]["score"] == 5.0


def test_checkpointless_zombie_gets_cold_rerun(stores):
    meta, store, sub_id = stores
    # killed before the first throttled checkpoint: RUNNING, no ckpt
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.update_trial(t["id"], heartbeat_at=0.0)

    w2 = _worker(ToyModel, meta, store, sub_id, "wB", trials=0)
    assert w2.resume_orphaned_trials() == 1
    by_status = {x["status"]: x for x in
                 meta.get_trials_of_sub_train_job(sub_id)}
    assert by_status["TERMINATED"]["id"] == t["id"]  # no zombie row
    assert by_status["COMPLETED"]["score"] == 5.0  # full cold re-run


def test_failed_resume_chains_warm_state(stores):
    meta, store, sub_id = stores

    class AlwaysFail(ToyModel):
        FAIL_AT = 0

    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    old = meta.get_trials_of_sub_train_job(sub_id)[0]
    # the resume attempt ALSO crashes → warm state must remain reachable
    # from the NEW (errored) row, since the old row is TERMINATED and
    # never scanned again
    w2 = _worker(AlwaysFail, meta, store, sub_id, "w1", trials=0)
    assert w2.resume_orphaned_trials() == 1
    errored = [t for t in meta.get_trials_of_sub_train_job(sub_id)
               if t["status"] == "ERRORED"]
    assert len(errored) == 1 and errored[0]["worker_id"] == "w1"
    # pre-seeded checkpoint + GLOBAL progress sidecar on the new row
    new_ckpt = store.load(f"ckpt-{errored[0]['id']}")
    assert new_ckpt is not None
    meta_blob = store.load(f"ckpt-{errored[0]['id']}-meta")
    assert meta_blob and meta_blob["frac_done"] >= 3 / 5
    # the new row records the ORIGINAL budget scale, so a third worker
    # resuming it computes the remainder against the true total
    assert errored[0]["budget_scale"] == 1.0
    # failed resume → the orphan's own blob is conservatively KEPT (the
    # pre-seed might not have happened); only a completed resume deletes
    assert store.load(f"ckpt-{old['id']}") is not None

    # and the chain actually completes: a third worker finishes it warm
    w3 = _worker(ToyModel, meta, store, sub_id, "w2", trials=0)
    assert w3.resume_orphaned_trials() == 1
    done = [t for t in meta.get_trials_of_sub_train_job(sub_id)
            if t["status"] == "COMPLETED"]
    assert len(done) == 1 and done[0]["score"] == 5.0


def test_original_error_text_preserved_on_claim(stores):
    meta, store, sub_id = stores
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    tid = meta.get_trials_of_sub_train_job(sub_id)[0]["id"]
    assert meta.claim_trial_for_resume(tid, "w1") is True
    err = meta.get_trial(tid)["error"]
    assert "simulated preemption" in err and "resumed by w1" in err


def test_end_of_run_linger_catches_fresh_orphan(stores):
    meta, store, sub_id = stores
    # peer wA died seconds ago: RUNNING, heartbeat fresh-ish but about to
    # turn stale; the advisor-exhausted worker must linger and claim it
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.heartbeat_trial(t["id"])
    store.save(f"ckpt-{t['id']}", {"w": np.asarray(3.0)})
    store.save(f"ckpt-{t['id']}-meta", {"frac_done": 3 / 5})

    w2 = _worker(ToyModel, meta, store, sub_id, "wB", trials=0)
    w2.orphan_stale_s = 1.5
    w2.heartbeat_interval_s = 0.3
    assert w2.run(max_trials=None) == 1  # advisor empty → linger resumes
    by_status = {x["status"]: x for x in
                 meta.get_trials_of_sub_train_job(sub_id)}
    assert by_status["COMPLETED"]["score"] == 5.0
    assert by_status["TERMINATED"]["id"] == t["id"]


def test_linger_exits_early_when_peer_finishes(stores):
    import threading
    import time

    meta, store, sub_id = stores
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.heartbeat_trial(t["id"])

    def finish_soon():
        time.sleep(0.6)
        meta.mark_trial_completed(t["id"], 1.0, params_saved=False)

    threading.Thread(target=finish_soon, daemon=True).start()
    w2 = _worker(ToyModel, meta, store, sub_id, "wB", trials=0)
    w2.orphan_stale_s = 30.0  # linger window long; must NOT wait it out
    t0 = time.monotonic()
    assert w2.run(max_trials=None) == 0
    assert time.monotonic() - t0 < 10.0  # exited when the peer completed
    assert meta.get_trial(t["id"])["status"] == "COMPLETED"  # untouched


def test_restarted_worker_reclaims_own_orphan(stores):
    meta, store, sub_id = stores
    # process 1 of worker "w0" dies mid-trial (stale heartbeat)
    _worker(FlakyToyModel, meta, store, sub_id, "w0", 1).run(max_trials=1)
    # process 2 boots with the SAME deterministic worker_id (restart
    # adoption); its own-trial set is empty, so it must reclaim
    w_restarted = _worker(ToyModel, meta, store, sub_id, "w0", trials=0)
    assert w_restarted.resume_orphaned_trials() == 1
    done = [t for t in meta.get_trials_of_sub_train_job(sub_id)
            if t["status"] == "COMPLETED"]
    assert len(done) == 1 and done[0]["score"] == 5.0


def test_fenced_completion_after_claim(stores):
    meta, store, sub_id = stores
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="wA",
                          knobs={})
    # wA stalls >stale window; wB claims the row
    meta.update_trial(t["id"], heartbeat_at=0.0)
    assert meta.claim_trial_for_resume(t["id"], "wB") is True
    # wA un-stalls and tries to finish: the fence must refuse — the row
    # stays TERMINATED and wA learns not to feed the advisor
    assert meta.mark_trial_completed(t["id"], 0.9,
                                     params_saved=True) is False
    assert meta.get_trial(t["id"])["status"] == "TERMINATED"
    assert meta.mark_trial_errored(t["id"], "late error") is False


def test_respawned_worker_same_name_lingers_for_predecessor(stores):
    meta, store, sub_id = stores
    # dead predecessor "w0" left trial RUNNING with a recent heartbeat
    # (killed moments ago); the REPLACEMENT inherits the same worker_id,
    # so the linger must key on per-process trial ids, not the name
    t = meta.create_trial(sub_id, 0, model_id="m0", worker_id="w0",
                          knobs={"max_epochs": 5, "share_params": False})
    meta.heartbeat_trial(t["id"])
    store.save(f"ckpt-{t['id']}", {"w": np.asarray(3.0)})
    store.save(f"ckpt-{t['id']}-meta", {"frac_done": 3 / 5})

    w2 = _worker(ToyModel, meta, store, sub_id, "w0", trials=0)  # SAME id
    w2.orphan_stale_s = 1.5
    w2.heartbeat_interval_s = 0.3
    assert w2.run(max_trials=None) == 1  # lingered until stale, resumed
    done = [x for x in meta.get_trials_of_sub_train_job(sub_id)
            if x["status"] == "COMPLETED"]
    assert len(done) == 1 and done[0]["score"] == 5.0
