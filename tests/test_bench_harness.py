"""Bench harness: the deadline parent must ABANDON an overdue
accelerator child, never kill it (a SIGKILLed TPU claimant leaves a
stale lease that poisons the tunnel for later claimants)."""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _bench_common import read_records, run_child  # noqa: E402


@pytest.mark.slow  # pays a real multi-second abandonment deadline
def test_overdue_child_is_abandoned_not_killed(tmp_path):
    script = tmp_path / "fake_bench.py"
    script.write_text(textwrap.dedent("""
        import json, sys, time
        if sys.argv[1] == "--child":
            with open(sys.argv[2], "a") as f:
                f.write(json.dumps({"stage": "probe"}) + "\\n")
            time.sleep(60)  # a blocked tunnel claim
            with open(sys.argv[2], "a") as f:
                f.write(json.dumps({"stage": "late"}) + "\\n")
    """))
    out = str(tmp_path / "stages.jsonl")
    proc = run_child(str(script), out, budget=6.0, env=dict(os.environ),
                     kill_on_timeout=False)
    # the parent's wait returned, but the child is STILL RUNNING
    assert proc.poll() is None, "abandoned child was killed"
    records = read_records(out)
    assert [r["stage"] for r in records] == ["probe"]
    proc.kill()  # test cleanup only — not a TPU claimant
    proc.wait()


def test_kill_on_timeout_still_available(tmp_path):
    script = tmp_path / "fake_bench.py"
    script.write_text("import time, sys; time.sleep(20)")
    proc = run_child(str(script), str(tmp_path / "o.jsonl"), budget=1.0,
                     env=dict(os.environ), kill_on_timeout=True)
    assert proc.poll() is not None  # killed and reaped
