"""No free device slot for an inference replica → loud failure, no CPU pin."""

import pytest

from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.parallel.mesh import DeviceSpec
from rafiki_tpu.store.meta_store import MetaStore


def test_inference_replica_requires_slot(tmp_path):
    meta = MetaStore(str(tmp_path / "meta.db"))
    user = meta.create_user("op@x", "pw", "ADMIN")
    model = meta.create_model(user["id"], "m", "IMAGE_CLASSIFICATION",
                              "M", b"class M: pass\n")
    job = meta.create_train_job(user["id"], "app", 1,
                                "IMAGE_CLASSIFICATION", {"TRIAL_COUNT": 1},
                                "d1", "d2")
    sub = meta.create_sub_train_job(job["id"], model["id"])
    trial = meta.create_trial(sub["id"], 0, model["id"], {"k": 1})
    meta.mark_trial_completed(trial["id"], 0.9, params_saved=True)
    ijob = meta.create_inference_job(user["id"], job["id"])

    mgr = ServicesManager(meta, str(tmp_path / "wd"), slot_size=1,
                          platform="cpu",
                          devices=[DeviceSpec(id=0)], slot_timeout=0.2)
    mgr.allocator.acquire()  # someone else holds the only slot
    try:
        with pytest.raises(RuntimeError, match="no free device slot"):
            mgr.create_inference_services(ijob["id"], max_workers=1)
        assert meta.get_inference_job(ijob["id"])["status"] == "ERRORED"
        # nothing left running or holding a slot
        assert not mgr.services
        assert mgr.allocator.free_count() == 0
    finally:
        mgr.stop_all()
