"""Draft-MODEL speculative decoding: a smaller model drafts, the
target verifies — greedy-lossless by construction, with the draft's
KV cache synced through prompts/scan/verify by mirrored multi-token
passes (serving/decode_engine.py ``draft=``)."""

import numpy as np
import pytest

from rafiki_tpu.models.llama_lora import LlamaLoRA

from test_decode_engine import KNOBS  # noqa: F401 — shared knobs
from test_multi_adapter import _lora_variant  # noqa: F401


def _drain(eng):
    got = {}
    for _ in range(400):
        if not eng.busy:
            break
        eng.step()
        for rid, text in eng.poll():
            got[rid] = text
    assert not eng.busy, "engine failed to drain"
    return got


def _serve(trained, reqs, **engine_kwargs):  # noqa: F811
    eng = trained.make_decode_engine(max_slots=4, max_new_tokens=8,
                                     **engine_kwargs)
    for rid, text in reqs:
        eng.submit(rid, text)
    return _drain(eng), eng


def test_draft_model_speculation_is_lossless(trained):  # noqa: F811
    """Outputs are token-identical to plain greedy decoding whether
    the draft is PERFECT (the target itself — near-total acceptance)
    or BAD (perturbed adapters — low acceptance): the verify step is
    target-authoritative either way."""
    reqs = [("a", "tok1 tok2 tok3"), ("b", "tok4 tok5"),
            ("c", "tok6 tok7 tok8")]
    plain, _ = _serve(trained, reqs)

    # perfect draft: a sibling carrying the same params
    perfect = LlamaLoRA(**KNOBS)
    perfect.load_parameters(trained.dump_parameters())
    out_p, eng_p = _serve(trained, reqs, speculate_k=4,
                          draft_model=perfect)
    assert out_p == plain
    s = eng_p.stats
    assert s.get("spec_draft_model_calls", 0) > 0, s
    assert s["spec_accepted"] > 0
    # a perfect draft should accept nearly everything it drafts
    assert s["spec_accepted"] >= 0.9 * s["spec_drafted"], s

    # bad draft: same base, perturbed adapters — still lossless
    bad = LlamaLoRA(**KNOBS)
    dump = trained.dump_parameters()
    dump = dict(dump)
    dump["params"] = _lora_variant(trained._params, scale=0.5)
    bad.load_parameters(dump)
    out_b, eng_b = _serve(trained, reqs, speculate_k=4, draft_model=bad)
    assert out_b == plain
    assert eng_b.stats["requests_done"] == len(reqs)


@pytest.mark.slow
def test_distilled_small_draft_partial_acceptance():
    """VERDICT r4 item 5 contract (the bench_extra small-draft leg):
    a genuinely smaller draft (depth 1, 1/4 width) distilled on the
    target's own greedy continuations, evaluated 2 tokens past the
    distillation horizon, must (a) land acceptance STRICTLY inside
    (0, 1) — neither the degenerate self-draft 1.0 nor a gated-off 0 —
    and (b) stay lossless: token-identical to plain greedy decode.
    Builds from the bench's OWN recipe (build_small_draft_setup), so
    this pins the exact configuration the bench measures."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from bench_extra import build_small_draft_setup

    from rafiki_tpu.serving.decode_engine import DecodeEngine

    (t_mod, t_params, d_mod, d_params, evs, max_new,
     _loss) = build_small_draft_setup(on_accel=False)

    def run(spec_k, draft=None):
        eng = DecodeEngine(t_mod, t_params, max_slots=4,
                           max_len=t_mod.max_len, speculate_k=spec_k,
                           draft=draft)
        for r, e in enumerate(evs):
            eng.submit(("r", r), e, max_new)
        got = {}
        for _ in range(500):
            if not eng.busy:
                break
            eng.step()
            for rid, toks in eng.poll():
                got[rid] = list(toks)
        assert not eng.busy
        return got, dict(eng.stats)

    plain, _ = run(0)
    spec, st = run(4, draft=(d_mod, d_params))
    assert spec == plain  # lossless regardless of acceptance
    acc = st["spec_accepted"] / max(1, st["spec_drafted"])
    assert st["spec_drafted"] > 0, st
    assert 0.0 < acc < 1.0, (acc, st)


def test_draft_model_mid_flight_admission(trained):  # noqa: F811
    """Requests admitted while others are mid-generation keep the
    draft cache synced (the scan/prefill mirrors): outputs still match
    solo plain decoding per request."""
    perfect = LlamaLoRA(**KNOBS)
    perfect.load_parameters(trained.dump_parameters())
    eng = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                     speculate_k=3,
                                     draft_model=perfect)
    plain_eng = trained.make_decode_engine(max_slots=2,
                                           max_new_tokens=6)
    for rid, text in [("a", "tok1 tok2 tok3"), ("b", "tok4 tok5"),
                      ("c", "tok6 tok7")]:
        plain_eng.submit(rid, text)
    plain = _drain(plain_eng)
    eng.submit("a", "tok1 tok2 tok3")
    got = {}
    stepped = 0
    while eng.busy or stepped == 0:
        eng.step()
        stepped += 1
        if stepped == 2:  # admit mid-flight
            eng.submit("b", "tok4 tok5")
        if stepped == 4:
            eng.submit("c", "tok6 tok7")
        for rid, text in eng.poll():
            got[rid] = text
        if stepped > 400:
            raise AssertionError("no drain")
    assert got == plain


def test_draft_model_vocab_mismatch_rejected(trained):  # noqa: F811
    other = LlamaLoRA(**{**KNOBS, "vocab_size": 1 << 9})
    other._params = other._module().init(
        __import__("jax").random.PRNGKey(0),
        np.zeros((1, int(KNOBS["max_len"])), np.int32))["params"]
    with pytest.raises(ValueError, match="vocab"):
        trained.make_decode_engine(speculate_k=3, draft_model=other)


def test_draft_with_prefix_cache_stays_accepted(trained):  # noqa: F811
    """system_prefix + draft_model: the prefix KV installs into BOTH
    caches, so prefix-hit requests keep near-total acceptance with a
    perfect draft (and stay lossless)."""
    perfect = LlamaLoRA(**KNOBS)
    perfect.load_parameters(trained.dump_parameters())
    prefix = "tok1 tok2 tok3"
    plain = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                       system_prefix=prefix)
    # spec_k=3 divides max_new: no stop-boundary clamp, so acceptance
    # measures draft quality alone
    eng = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                     speculate_k=3, draft_model=perfect,
                                     system_prefix=prefix)
    reqs = [("a", prefix + " tok4 tok5"), ("b", prefix + " tok6")]
    for rid, text in reqs:
        plain.submit(rid, text)
    ref = _drain(plain)
    for rid, text in reqs:
        eng.submit(rid, text)
    got = _drain(eng)
    assert got == ref
    s = eng.stats
    assert s["prefix_hits"] == 2
    assert s["spec_accepted"] >= 0.9 * s["spec_drafted"], s


def test_draft_resync_after_gated_stretch(trained):  # noqa: F811
    """Force the gate off (sampling traffic skips spec and the mirror),
    then greedy traffic re-probes: the engine resyncs the draft cache
    from accepted contexts and keeps outputs lossless."""
    perfect = LlamaLoRA(**KNOBS)
    perfect.load_parameters(trained.dump_parameters())
    eng = trained.make_decode_engine(max_slots=2, max_new_tokens=6,
                                     speculate_k=3, draft_model=perfect)
    # sampling requests ride the scan path; the engine skips mirrors
    # while the spec path is unavailable only if gated — force the gate
    # down artificially to exercise resync deterministically
    eng.engine._spec_ema = 0.0
    eng.submit("warm", "tok1 tok2")
    _drain(eng)
    assert eng.engine._draft_synced is False
    eng.engine._spec_ema = eng.engine._spec_floor + 1.0  # re-open
    plain = trained.make_decode_engine(max_slots=2, max_new_tokens=6)
    plain.submit("x", "tok1 tok2 tok3")
    ref = _drain(plain)
    eng.submit("x", "tok1 tok2 tok3")
    got = _drain(eng)
    assert got == ref
    assert eng.engine.stats["draft_resyncs"] >= 1
