"""DenseNet family: module shapes, template contract, DP training."""

import pytest

import jax
import numpy as np

from rafiki_tpu.constants import TaskType
from rafiki_tpu.data import generate_image_classification_dataset
from rafiki_tpu.model import TrainContext, test_model_class
from rafiki_tpu.models.densenet import DenseNet, DenseNetClassifier

TINY = {"variant": "densenet-s", "growth": 12, "batch_size": 32,
        "max_epochs": 5, "learning_rate": 0.05, "weight_decay": 1e-4,
        "bf16": False, "quick_train": False, "share_params": False}


def test_densenet_module_shapes():
    m = DenseNet(block_sizes=(2, 2), growth=8, n_classes=7)
    x = np.zeros((2, 32, 32, 3), np.float32)
    variables = m.init(jax.random.PRNGKey(0), x, train=False)
    out = m.apply(variables, x, train=False)
    assert out.shape == (2, 7)
    # dense connectivity: the LAST layer of block 0 must see the concat
    # of the stem (2k) plus one k-growth from the preceding layer — a
    # regression that drops the concat would shrink this input width
    p = variables["params"]
    last_layer = p["_DenseLayer_1"]["Conv_0"]["kernel"]  # 1x1 bottleneck
    assert last_layer.shape[-2] == 2 * 8 + 8  # stem + 1 * growth


@pytest.mark.slow
def test_densenet_template_contract(tmp_path):
    tr, va = str(tmp_path / "t.npz"), str(tmp_path / "v.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    ds = generate_image_classification_dataset(va, 48, seed=1)
    preds = test_model_class(DenseNetClassifier,
                             TaskType.IMAGE_CLASSIFICATION,
                             tr, va, queries=[ds.images[0]], knobs=TINY)
    assert len(preds) == 1 and len(preds[0]) == ds.n_classes


@pytest.mark.slow
def test_densenet_trains_data_parallel(tmp_path):
    """Train over 8 virtual devices; loss must decrease."""
    tr = str(tmp_path / "t.npz")
    generate_image_classification_dataset(tr, 192, seed=0)
    model = DenseNetClassifier(**TINY)
    ctx = TrainContext(devices=list(jax.devices()))
    model.train(tr, ctx)
    losses = ctx.logger.get_values("loss")
    assert len(losses) >= 2 and losses[-1] < losses[0]
