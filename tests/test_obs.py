"""The observability plane (rafiki_tpu/obs): histogram bucket math,
Prometheus text exposition, trace-ID propagation predictor→worker,
ring-buffer bounds under churn, /metrics on every service surface, and
stale-worker detection.

The pure-core tests run in milliseconds; the end-to-end legs ride the
session ``trained``/``trained_lm`` LM fixture like the rest of the
serving suite.
"""

import re
import threading
import time
import urllib.request

import pytest

from rafiki_tpu.obs import (Counter, Histogram, MetricsRegistry,
                            StatsMap, TraceBuffer, mint_trace_id,
                            sanitize_trace_id)

# ---------------------------------------------------------------- core


def test_histogram_bucket_math():
    h = Histogram("lat_seconds", buckets=(0.1, 1.0, 10.0))
    # boundary semantics are le (<=): an observation AT a bound lands
    # in that bound's bucket, just past it in the next
    h.observe(0.1)
    h.observe(0.100001)
    h.observe(5.0)
    h.observe(99.0)   # +Inf bucket
    assert h.count == 4
    assert h.sum == pytest.approx(0.1 + 0.100001 + 5.0 + 99.0)
    lines = h.expose()
    by_le = {}
    for ln in lines:
        m = re.match(r'lat_seconds_bucket\{le="([^"]+)"\} (\d+)', ln)
        if m:
            by_le[m.group(1)] = int(m.group(2))
    assert by_le["0.1"] == 1          # the exact-boundary observation
    assert by_le["1.0"] == 2          # cumulative: +0.100001
    assert by_le["10.0"] == 3         # +5.0
    assert by_le["+Inf"] == 4         # everything, == _count
    # cumulative counts are monotone
    vals = [by_le[k] for k in ("0.1", "1.0", "10.0", "+Inf")]
    assert vals == sorted(vals)
    # sum/count invariant rides the exposition too
    assert any(ln.startswith("lat_seconds_count 4") for ln in lines)
    assert any(ln.startswith("lat_seconds_sum ") for ln in lines)


def test_histogram_quantile_estimates():
    h = Histogram("q", buckets=(1, 2, 4, 8))
    for v in (0.5, 1.5, 1.5, 3.0, 7.0):
        h.observe(v)
    # p50: target rank 3 of 5 -> inside the (1, 2] bucket
    assert 1.0 <= h.quantile(0.5) <= 2.0
    # p99 -> the (4, 8] bucket
    assert 4.0 <= h.quantile(0.99) <= 8.0
    # monotone in p
    qs = [h.quantile(p) for p in (0.1, 0.5, 0.9, 0.99)]
    assert qs == sorted(qs)
    # +Inf-bucket mass clamps to the last finite bound
    h2 = Histogram("q2", buckets=(1,))
    h2.observe(50.0)
    assert h2.quantile(0.99) == 1.0
    assert Histogram("q3", buckets=(1,)).quantile(0.5) == 0.0  # empty


def test_prometheus_exposition_is_valid():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(3)
    reg.gauge("live_gauge", "live", fn=lambda: 7)
    reg.histogram("h_seconds", buckets=(0.5, 5.0)).observe(0.1)
    sm = StatsMap({"kv_pages_used": 2, "admission_stalls": 0})
    reg.register_stats(sm)
    text = reg.render_prometheus()
    sample = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'   # optional label set
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? '
        r"[-+0-9.eEInfa]+$")                   # value (incl. +Inf)
    for ln in text.strip().splitlines():
        assert ln.startswith("#") or sample.match(ln), ln
    # the hand-rolled-dict replacement surfaces under its bare names
    assert "kv_pages_used 2" in text
    assert "# TYPE h_seconds histogram" in text
    assert 'h_seconds_bucket{le="+Inf"} 1' in text
    assert "live_gauge 7" in text
    # flat snapshot view for hub publishing
    snap = reg.snapshot()
    assert snap["req_total"] == 3 and snap["kv_pages_used"] == 2
    assert snap["h_seconds_count"] == 1


def test_registry_type_conflicts_and_names():
    reg = MetricsRegistry()
    reg.counter("a_total")
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("a_total")
    with pytest.raises(ValueError, match="invalid metric name"):
        reg.counter("bad name")


def test_stats_map_snapshot_race_free():
    """Concurrent inc + snapshot/iteration: the crash mode this class
    exists to end is `dictionary changed size during iteration`."""
    sm = StatsMap()
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            sm.inc(f"k{i % 50}")
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    try:
        for _ in range(300):
            dict(sm)          # iterates via locked snapshot
            sm.snapshot()
    finally:
        stop.set()
        t.join(timeout=5)


def test_trace_ring_bounds_under_churn():
    tb = TraceBuffer(maxlen=8)
    for i in range(100):
        tb.start(f"t{i}", request_id=str(i))
    assert len(tb) == 8
    recent = tb.recent(100)
    assert [r["trace_id"] for r in recent] == \
        [f"t{i}" for i in range(99, 91, -1)]
    # live records still take spans; evicted ones recreate a fragment
    tb.add_span("t99", "done", tokens=3)
    assert [s["name"] for s in tb.get("t99")["spans"]] == \
        ["queued", "done"]
    tb.add_span("t0", "late")  # evicted long ago — fragment, not a loss
    assert tb.get("t0")["spans"][0]["name"] == "late"
    assert len(tb) == 8  # still bounded


def test_trace_id_sanitization():
    assert sanitize_trace_id("abc-123.X:y") == "abc-123.X:y"
    assert sanitize_trace_id("  padded  ") == "padded"
    assert sanitize_trace_id("bad id") == ""      # whitespace inside
    assert sanitize_trace_id("x" * 200) == ""     # oversized
    assert sanitize_trace_id(None) == ""
    assert len(mint_trace_id()) == 32


# ------------------------------------------------------- service surfaces


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.headers.get("Content-Type", ""), resp.read().decode()


def test_trace_propagation_predictor_to_worker(trained_lm):
    """Acceptance leg: one request's trace ID, supplied via
    X-Rafiki-Trace-Id, is followable across the predictor's AND the
    worker's /debug/requests, with the request-lifecycle spans
    (queued → admitted → first_token → done) on the worker side and
    TTFT/e2e histograms fed on both /metrics surfaces."""
    from test_decode_engine import KNOBS as LM_KNOBS

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import (Predictor,
                                              PredictorService)
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.utils.http import json_request
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("lm-obs", trained_lm.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "lm-obs", LM_KNOBS, store, hub,
                             "w-obs", decode_loop=True, max_slots=4,
                             max_new_tokens=4)
    w_host, w_port = worker.serve_obs()
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    svc = PredictorService(Predictor(hub, ["w-obs"],
                                     gather_timeout=120.0))
    host, port = svc.start()
    tid = "e2e-trace-0042"
    try:
        req = urllib.request.Request(
            f"http://{host}:{port}/predict",
            data=b'{"queries": ["tok1 tok2 tok3"]}',
            headers={"Content-Type": "application/json",
                     "X-Rafiki-Trace-Id": tid}, method="POST")
        import json as _json

        with urllib.request.urlopen(req, timeout=120) as resp:
            out = _json.loads(resp.read())
        assert out["predictions"] and out["predictions"][0]
        # the honored trace id comes back in info
        assert out["info"]["trace_id"] == tid

        # predictor side: received → scattered → reply → done
        pred_dbg = json_request(
            "GET", f"http://{host}:{port}/debug/requests?n=16")
        rec_p = next(r for r in pred_dbg["requests"]
                     if r["trace_id"] == tid)
        names_p = [s["name"] for s in rec_p["spans"]]
        assert names_p[0] == "received" and "done" in names_p
        assert "reply" in names_p

        # worker side, SAME trace id: the decode-loop lifecycle
        wrk_dbg = json_request(
            "GET", f"http://{w_host}:{w_port}/debug/requests?n=16")
        rec_w = next(r for r in wrk_dbg["requests"]
                     if r["trace_id"] == tid)
        names_w = [s["name"] for s in rec_w["spans"]]
        for expected in ("queued", "admitted", "first_token", "done"):
            assert expected in names_w, (expected, names_w)
        # span order: queued before admitted before first_token ≤ done
        assert names_w.index("queued") < names_w.index("admitted") \
            < names_w.index("first_token")

        # both /metrics surfaces render valid text with the latency
        # histograms the acceptance criteria name
        ctype, pred_metrics = _get(f"http://{host}:{port}/metrics")
        assert ctype.startswith("text/plain")
        assert "request_seconds_bucket" in pred_metrics
        assert "requests_served 1" in pred_metrics
        _, wrk_metrics = _get(f"http://{w_host}:{w_port}/metrics")
        assert "ttft_seconds_bucket" in wrk_metrics
        assert "request_seconds_bucket" in wrk_metrics
        # engine gauges keep their bare names on the worker surface
        assert "tokens_generated" in wrk_metrics
        assert re.search(r"^kv_pages_used \d", wrk_metrics, re.M)
    finally:
        svc.stop()
        worker.stop()
        wt.join(timeout=10)


def test_worker_health_carries_ttft_and_uptime(trained_lm):
    """The hub-published stats now carry the monotonic staleness pair
    (uptime_s / stale_after_s) plus bucket-derived TTFT/e2e summaries —
    what the dashboard's worker line renders."""
    from test_decode_engine import KNOBS as LM_KNOBS

    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub
    from rafiki_tpu.store.param_store import ParamStore
    from rafiki_tpu.worker.inference import InferenceWorker

    store = ParamStore.from_uri("mem://")
    store.save("lm-h", trained_lm.dump_parameters())
    hub = InProcQueueHub()
    worker = InferenceWorker(LlamaLoRA, "lm-h", LM_KNOBS, store, hub,
                             "w-h", decode_loop=True, max_slots=2,
                             max_new_tokens=3)
    wt = threading.Thread(target=worker.run, daemon=True)
    wt.start()
    try:
        pred = Predictor(hub, ["w-h"], gather_timeout=120.0)
        preds, info = pred.predict(["tok1 tok2"])
        assert preds and preds[0]
        worker._publish_stats()
        s = pred.stats()["workers"]["w-h"]
        assert s["uptime_s"] > 0 and s["stale_after_s"] > 0
        assert s["stale"] is False
        assert s["ttft_p50_s"] > 0 and s["e2e_p95_s"] > 0
        assert s["engine_requests_done"] >= 1
    finally:
        worker.stop()
        wt.join(timeout=10)


def test_predictor_marks_stale_workers():
    """Monotonic staleness: a worker whose published uptime_s stops
    advancing past its stale_after_s budget greys out; a republish with
    advanced uptime clears it. Wall-clock (published_at) never enters
    the decision."""
    from rafiki_tpu.serving.predictor import Predictor
    from rafiki_tpu.serving.queues import InProcQueueHub

    hub = InProcQueueHub()
    pred = Predictor(hub, ["w0"], gather_timeout=1.0)
    hub.put_worker_stats("w0", {"uptime_s": 5.0, "stale_after_s": 0.15,
                                "published_at": 0.0})  # ancient wall ts
    assert pred.stats()["workers"]["w0"]["stale"] is False  # fresh sight
    time.sleep(0.25)  # uptime unchanged past the budget
    assert pred.stats()["workers"]["w0"]["stale"] is True
    hub.put_worker_stats("w0", {"uptime_s": 6.0, "stale_after_s": 0.15})
    assert pred.stats()["workers"]["w0"]["stale"] is False  # advanced
    time.sleep(0.25)
    # a RESPAWNED worker restarts uptime near 0 — any uptime CHANGE
    # refreshes the watermark, so the healthy replacement is never
    # greyed out waiting to outlive its dead predecessor's uptime
    hub.put_worker_stats("w0", {"uptime_s": 0.4, "stale_after_s": 0.15})
    assert pred.stats()["workers"]["w0"]["stale"] is False
    # legacy publisher (no uptime_s): wall-clock fallback
    hub.put_worker_stats("w1", {"published_at": time.time() - 9999.0})
    pred2 = Predictor(hub, ["w1"], gather_timeout=1.0)
    assert pred2.stats()["workers"]["w1"]["stale"] is True


def test_admin_metrics_surface(tmp_path):
    """GET /metrics on the admin app: control-plane gauges evaluated
    live + the HTTP self-instrumentation."""
    from rafiki_tpu.admin.admin import Admin
    from rafiki_tpu.admin.app import AdminApp
    from rafiki_tpu.admin.services_manager import ServicesManager
    from rafiki_tpu.parallel.mesh import DeviceSpec
    from rafiki_tpu.store.meta_store import MetaStore

    meta = MetaStore(str(tmp_path / "meta.db"))
    manager = ServicesManager(meta, str(tmp_path), slot_size=1,
                              platform="cpu",
                              devices=[DeviceSpec(id=0)])
    app = AdminApp(Admin(meta, manager))
    host, port = app.start()
    try:
        ctype, text = _get(f"http://{host}:{port}/metrics")
        assert ctype.startswith("text/plain")
        assert "admin_services 0" in text
        assert "admin_free_slots 1" in text
        assert "admin_respawns_done 0" in text
        # the scrape itself was counted (second scrape sees >= 1)
        _, text2 = _get(f"http://{host}:{port}/metrics")
        assert re.search(r"^http_requests_total [1-9]", text2, re.M)
        # the admin's trace ring carries user-owned job metadata:
        # unauthenticated pulls 401 (unlike the worker/predictor
        # surfaces, which have no auth model by design)
        from rafiki_tpu.utils.http import json_request

        with pytest.raises(RuntimeError, match="401"):
            json_request("GET",
                         f"http://{host}:{port}/debug/requests")
        token = json_request(
            "POST", f"http://{host}:{port}/tokens",
            {"email": "superadmin@rafiki",
             "password": "rafiki"})["token"]
        out = json_request(
            "GET", f"http://{host}:{port}/debug/requests",
            headers={"Authorization": f"Bearer {token}"})
        assert out["requests"] == []
    finally:
        app.stop()


def test_train_worker_metrics_and_trial_timeline(tmp_path, monkeypatch):
    """The train worker's obs surface: trial_seconds histogram +
    trials_completed counter on /metrics, a per-trial timeline in
    /debug/requests, and throughput records (tokens_per_s + est_mfu
    under a pinned peak-FLOPs denominator) in the trial logs."""
    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.data import generate_text_classification_dataset
    from rafiki_tpu.models.llama_lora import LlamaLoRA
    from rafiki_tpu.utils.http import json_request
    from rafiki_tpu.worker.train import TrainWorker
    from test_decode_engine import KNOBS as LM_KNOBS

    monkeypatch.setenv("RAFIKI_DEVICE_PEAK_FLOPS", "1e12")
    tr = str(tmp_path / "tr.jsonl")
    va = str(tmp_path / "va.jsonl")
    generate_text_classification_dataset(tr, 48, seed=0)
    generate_text_classification_dataset(va, 16, seed=1)
    advisor = make_advisor(LlamaLoRA.get_knob_config(), "random",
                           total_trials=1, seed=0)
    # pin the searchable knobs to the tiny test scale; fixed knobs
    # (max_epochs/vocab_size) keep their config values — overriding a
    # FixedKnob is a validation error by design (quick_train caps the
    # epochs anyway)
    overrides = {k: v for k, v in LM_KNOBS.items()
                 if k not in ("max_epochs", "vocab_size", "hidden_dim")}
    overrides["hidden_dim"] = 64
    worker = TrainWorker(LlamaLoRA, advisor, tr, va,
                         knob_overrides=overrides,
                         checkpoint_interval_s=0)
    host, port = worker.serve_obs()
    try:
        assert worker.run(max_trials=1) == 1
        ctype, text = _get(f"http://{host}:{port}/metrics")
        assert ctype.startswith("text/plain")
        assert "trials_completed 1" in text
        assert "trial_seconds_bucket" in text
        assert re.search(r"^last_trial_tokens_per_s [0-9.]*[1-9]",
                         text, re.M)
        assert re.search(r"^last_trial_est_mfu [0-9.e-]*[1-9]",
                         text, re.M)
        dbg = json_request("GET",
                           f"http://{host}:{port}/debug/requests")
        spans = [s["name"] for s in dbg["requests"][0]["spans"]]
        assert spans[0] == "trial_start" and "trial_done" in spans
        done = next(s for s in dbg["requests"][0]["spans"]
                    if s["name"] == "trial_done")
        assert done["tokens_per_s"] > 0 and done["est_mfu"] > 0
    finally:
        worker.stop_obs()


def test_engine_span_events_direct(trained):
    """The DecodeEngine's span hook fires the documented lifecycle on a
    raw (token-level) engine, and a broken sink detaches instead of
    killing the step loop."""
    import numpy as np

    from rafiki_tpu.serving.decode_engine import DecodeEngine

    module, params = trained._module(), trained._params
    eng = DecodeEngine(module, params, max_slots=2, max_len=32)
    events = []
    eng.span_sink = lambda ev, rid, attrs: events.append((ev, rid))
    eng.submit("r1", np.asarray([1, 5, 9], np.int32), 3)
    for _ in range(32):
        if not eng.busy:
            break
        eng.step()
    assert dict(eng.poll())["r1"]
    names = [ev for ev, rid in events if rid == "r1"]
    assert names[0] == "admitted"
    assert "first_token" in names and names[-1] == "done"
    assert names.index("admitted") < names.index("first_token")

    def boom(ev, rid, attrs):
        raise RuntimeError("sink broke")

    eng.span_sink = boom
    eng.submit("r2", np.asarray([1, 2], np.int32), 2)
    for _ in range(32):
        if not eng.busy:
            break
        eng.step()  # must not raise
    assert dict(eng.poll())["r2"]
    assert eng.span_sink is None  # detached after the first failure
    # stats_snapshot is the locked read path
    snap = eng.stats_snapshot()
    assert snap["requests_done"] == 2
